// api_gateway worker — C++ equivalent of the reference's api_service
// (SURVEY.md §2 checklist item 8; reference: services/api_service/src/main.rs).
// HTTP/1.1 + SSE server, bus client behind; the reference's Next.js frontend
// works against this unmodified (§1-L4 contract):
//
// - POST /api/submit-url      → publish tasks.perceive.url (main.rs:42-111)
// - POST /api/generate-text   → validate task_id / 1..=max_length, publish
//                               tasks.generation.text (main.rs:113-188)
// - GET  /api/events          → SSE stream of events.text.generated, 15s
//                               keep-alive comments, drop-on-lag
//                               (main.rs:190-270; broadcast cap 32 :537)
// - POST /api/search/semantic → 2-hop request-reply orchestration, 15s/20s
//                               timeouts, the reference's exact status-code
//                               mapping: hop timeout → 503, service-reported
//                               error → 500 (main.rs:272-512)
// - CORS on localhost/127.0.0.1 origins (main.rs:555-567)
// - GET /api/metrics, /healthz (SURVEY.md §5.5/§5.3 additions)
//
// Thread model: accept loop + one detached thread per HTTP connection. Each
// search hop uses its own short-lived bus connection (symbus::Client is
// single-owner); publishes share a mutex-guarded client; one bridge thread
// owns the events.text.generated subscription and fans out to SSE clients
// through bounded per-client queues (capacity 32, drop-on-lag).
//
// Usage: api_gateway [SYMBIONT_BUS_URL=...] [SYMBIONT_API_HOST/PORT=...]

#include <cerrno>
#include <cstdlib>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <signal.h>
#include <sys/socket.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <deque>
#include <mutex>
#include <set>
#include <thread>
#include <utility>
#include <vector>

#include "../../generated/cpp/symbiont_schema.hpp"
#include "common.hpp"

namespace {

const char* SERVICE = "api_gateway";

// ------------------------------------------------------------------ metrics

class Metrics {
 public:
  void inc(const std::string& name, uint64_t n = 1) {
    std::lock_guard<std::mutex> g(mu_);
    counters_[name] += n;
  }
  std::string snapshot_json() {
    std::lock_guard<std::mutex> g(mu_);
    json::Value o = json::Value::object();
    json::Value c = json::Value::object();
    for (const auto& [k, v] : counters_) c.set(k, json::Value((double)v));
    o.set("counters", std::move(c));
    o.set("histograms", json::Value::object());
    return o.dump();
  }

 private:
  std::mutex mu_;
  std::map<std::string, uint64_t> counters_;
};

Metrics g_metrics;

// per-tenant admission (common.hpp AdmissionGate — the Python
// resilience/admission.py quota check, ported so the C++ gateway is no
// longer the one ingress a hot tenant could walk around; engine-plane
// tenant lanes stay the second line of defense behind this edge)
symbiont::AdmissionGate g_admission;

// ------------------------------------------------------------------ sse hub

// Clients may register with a task_id filter (?task_id= on /api/events): the
// reference broadcasts every generation event to every SSE client
// (main.rs:215-270 — its UI correlates by original_task_id client-side);
// unfiltered clients keep that behavior, filtered ones receive only their
// task's events.
class SseHub {
 public:
  struct Queue {
    std::mutex mu;
    std::condition_variable cv;
    std::deque<std::string> items;
    std::string task_filter;  // "" = unfiltered (broadcast semantics)
    bool closed = false;
  };

  std::shared_ptr<Queue> register_client(const std::string& task_filter = "") {
    auto q = std::make_shared<Queue>();
    q->task_filter = task_filter;
    std::lock_guard<std::mutex> g(mu_);
    clients_.push_back(q);
    return q;
  }

  void unregister(const std::shared_ptr<Queue>& q) {
    std::lock_guard<std::mutex> g(mu_);
    for (auto it = clients_.begin(); it != clients_.end(); ++it)
      if (*it == q) {
        clients_.erase(it);
        break;
      }
  }

  void broadcast(const std::string& payload, size_t capacity) {
    std::lock_guard<std::mutex> g(mu_);
    std::string event_tid;
    bool parsed = false;
    for (auto& q : clients_) {
      if (!q->task_filter.empty()) {
        if (!parsed) {  // parse once, only if some client filters
          parsed = true;
          try {
            json::Value v = json::parse(payload);
            if (v.is_object() && v.has("original_task_id") &&
                !v.at("original_task_id").is_null())
              event_tid = v.at("original_task_id").as_string();
          } catch (const std::exception&) {
            // unparseable payload: delivered to unfiltered clients only
          }
        }
        if (event_tid != q->task_filter) continue;  // not this client's task
      }
      std::lock_guard<std::mutex> qg(q->mu);
      if (q->items.size() >= capacity) {
        g_metrics.inc("api.sse_dropped");
        symbiont::logline("WARN", SERVICE, "SSE client lagged; dropping message");
        continue;
      }
      q->items.push_back(payload);
      q->cv.notify_one();
    }
  }

 private:
  std::mutex mu_;
  std::vector<std::shared_ptr<Queue>> clients_;
};

SseHub g_hub;

// ----------------------------------------------------------------- http bits

struct HttpRequest {
  std::string method, path;
  std::string query;  // raw query string (after '?'), "" if none
  std::map<std::string, std::string> headers;  // lowercase keys
  std::string body;
};

// On malformed/oversized requests that deserve an HTTP status (rather than a
// silent close), *err_status is set to 400/413 and false is returned.
bool read_http_request(int fd, HttpRequest& req, int timeout_ms,
                       int* err_status = nullptr) {
  std::string buf;
  char chunk[16384];
  size_t header_end = std::string::npos;
  int64_t deadline = (int64_t)symbiont::now_ms() + timeout_ms;
  while (header_end == std::string::npos) {
    int wait = (int)(deadline - (int64_t)symbiont::now_ms());
    if (wait <= 0) return false;
    struct pollfd p {fd, POLLIN, 0};
    int rc = ::poll(&p, 1, wait);
    if (rc <= 0) return false;
    ssize_t n = ::recv(fd, chunk, sizeof(chunk), 0);
    if (n <= 0) return false;
    buf.append(chunk, (size_t)n);
    if (buf.size() > 8 * 1024 * 1024) return false;
    header_end = buf.find("\r\n\r\n");
  }
  std::string head = buf.substr(0, header_end);
  req.body = buf.substr(header_end + 4);

  size_t line_end = head.find("\r\n");
  std::string start = head.substr(0, line_end);
  auto sp1 = start.find(' ');
  auto sp2 = start.find(' ', sp1 + 1);
  if (sp1 == std::string::npos || sp2 == std::string::npos) return false;
  req.method = start.substr(0, sp1);
  req.path = start.substr(sp1 + 1, sp2 - sp1 - 1);
  auto qmark = req.path.find('?');
  if (qmark != std::string::npos) {
    req.query = req.path.substr(qmark + 1);
    req.path.resize(qmark);
  }

  size_t pos = line_end + 2;
  while (pos < head.size()) {
    size_t eol = head.find("\r\n", pos);
    if (eol == std::string::npos) eol = head.size();
    std::string line = head.substr(pos, eol - pos);
    auto colon = line.find(':');
    if (colon != std::string::npos) {
      std::string k = line.substr(0, colon);
      for (auto& c : k) c = (char)std::tolower((unsigned char)c);
      std::string v = line.substr(colon + 1);
      size_t b = v.find_first_not_of(" \t");
      size_t e = v.find_last_not_of(" \t");
      req.headers[k] = b == std::string::npos ? "" : v.substr(b, e - b + 1);
    }
    pos = eol + 2;
  }

  long long announced = 0;
  auto cl = req.headers.find("content-length");
  if (cl != req.headers.end() && !cl->second.empty()) {
    // Python-twin parity: empty value == no body; otherwise strictly numeric
    const std::string& v = cl->second;
    size_t i = (v[0] == '-' || v[0] == '+') ? 1 : 0;
    bool numeric = v.size() > i;
    for (size_t j = i; j < v.size(); ++j)
      if (!std::isdigit((unsigned char)v[j])) numeric = false;
    if (!numeric) {
      if (err_status) *err_status = 400;
      return false;
    }
    errno = 0;
    announced = std::strtoll(v.c_str(), nullptr, 10);
    if (errno == ERANGE) {
      // out-of-range value must not silently wrap and mis-frame the body
      if (err_status) *err_status = (v[0] == '-') ? 400 : 413;
      return false;
    }
  }
  // cap the client-supplied length: negative wraps and huge values OOM
  if (announced < 0) {
    if (err_status) *err_status = 400;
    return false;
  }
  if (announced > 16 * 1024 * 1024) {
    if (err_status) *err_status = 413;
    return false;
  }
  size_t want = (size_t)announced;
  while (req.body.size() < want) {
    int wait = (int)(deadline - (int64_t)symbiont::now_ms());
    if (wait <= 0) return false;
    struct pollfd p {fd, POLLIN, 0};
    if (::poll(&p, 1, wait) <= 0) return false;
    ssize_t n = ::recv(fd, chunk, sizeof(chunk), 0);
    if (n <= 0) return false;
    req.body.append(chunk, (size_t)n);
  }
  req.body.resize(want);
  return true;
}

bool send_all(int fd, const std::string& data) {
  size_t off = 0;
  while (off < data.size()) {
    ssize_t n = ::send(fd, data.data() + off, data.size() - off, MSG_NOSIGNAL);
    if (n <= 0) return false;
    off += (size_t)n;
  }
  return true;
}

// exact host (+optional port): http://localhost.evil.com must NOT match
// (reference: main.rs:555-567)
std::string cors_headers(const std::map<std::string, std::string>& headers) {
  auto it = headers.find("origin");
  if (it == headers.end()) return "";
  const std::string& o = it->second;
  std::string rest;
  if (o.rfind("http://", 0) == 0) rest = o.substr(7);
  else if (o.rfind("https://", 0) == 0) rest = o.substr(8);
  else return "";
  std::string host = rest;
  auto colon = rest.find(':');
  if (colon != std::string::npos) {
    host = rest.substr(0, colon);
    std::string port = rest.substr(colon + 1);
    if (port.empty()) return "";
    for (char c : port)
      if (!std::isdigit((unsigned char)c)) return "";
  }
  if (host != "localhost" && host != "127.0.0.1") return "";
  return "Access-Control-Allow-Origin: " + o +
         "\r\nAccess-Control-Allow-Methods: GET, POST, OPTIONS\r\n"
         "Access-Control-Allow-Headers: Content-Type\r\nVary: Origin\r\n";
}

void write_response(int fd, int status, const std::string& body,
                    const std::map<std::string, std::string>& req_headers,
                    bool keep_alive, const std::string& extra_headers = "") {
  const char* reason = status == 200   ? "OK"
                       : status == 400 ? "Bad Request"
                       : status == 404 ? "Not Found"
                       : status == 413 ? "Payload Too Large"
                       : status == 429 ? "Too Many Requests"
                       : status == 503 ? "Service Unavailable"
                                       : "Internal Server Error";
  std::string head = "HTTP/1.1 " + std::to_string(status) + " " + reason +
                     "\r\nContent-Type: application/json\r\nContent-Length: " +
                     std::to_string(body.size()) + "\r\n" +
                     cors_headers(req_headers) + extra_headers +
                     (keep_alive ? "Connection: keep-alive\r\n\r\n"
                                 : "Connection: close\r\n\r\n");
  send_all(fd, head + body);
}

// Bundled single-page UI (frontend/index.html), loaded once at startup.
// Missing file is fine: the gateway serves the API without the UI, same as
// the reference where the frontend is a separate container
// (docker-compose.yml:131-145).
std::string g_frontend_html;

void load_frontend() {
  std::string path = symbiont::env_or("SYMBIONT_FRONTEND_PATH",
                                      "frontend/index.html");
  FILE* f = std::fopen(path.c_str(), "rb");
  if (!f) return;
  char buf[65536];
  size_t n;
  while ((n = std::fread(buf, 1, sizeof(buf), f)) > 0)
    g_frontend_html.append(buf, n);
  std::fclose(f);
}

std::string msg_json(const std::string& message) {
  json::Value o = json::Value::object();
  o.set("message", json::Value(message));
  o.set("task_id", json::Value());
  return o.dump();
}

// ------------------------------------------------------------------- config

struct Config {
  std::string host;
  int port;
  int max_gen_length;
  int sse_keepalive_ms;
  size_t sse_capacity;
  int embed_timeout_ms;
  int search_timeout_ms;
  int rerank_timeout_ms;
  int health_timeout_ms;
  bool fused_search;
  int fused_timeout_ms;
  int fused_down_ms;
  int fused_max_top_k;
};

Config g_cfg;

// readiness (GET /readyz): the HTTP port opens BEFORE the bus connection
// exists (so /healthz answers during bring-up), but a data-path POST
// accepted then would 200 into nothing — the exact cold-gateway window the
// compose healthcheck used to miss by probing /healthz. Flipped true once
// the SSE bridge's bus client is connected and subscribed.
std::atomic<bool> g_ready{false};

// negative cache: after a fused-search timeout (subject unserved), skip the
// fused probe until this steady-clock deadline so a deployment without a
// co-located engine+store pays the probe once per window, not per request
std::atomic<int64_t> g_fused_down_until_ms{0};

int64_t steady_now_ms() {
  return std::chrono::duration_cast<std::chrono::milliseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

// per-request bus connection (symbus::Client is single-owner)
bool fresh_bus(symbus::Client& c) {
  symbiont::BusAddr addr = symbiont::parse_bus_url(symbiont::env_or(
      "SYMBIONT_BUS_URL", symbiont::env_or("NATS_URL", "symbus://127.0.0.1:4233")));
  try {
    c.connect(addr.host, addr.port);
    return true;
  } catch (const std::exception& e) {
    symbiont::logline("WARN", SERVICE, std::string("bus connect failed: ") + e.what());
    return false;
  }
}

// shared publish-only client (submit-url / generate-text are single frames)
std::mutex g_pub_mu;
symbus::Client g_pub;

bool publish_locked(const std::string& subject, const std::string& data,
                    const std::map<std::string, std::string>& headers) {
  std::lock_guard<std::mutex> g(g_pub_mu);
  for (int attempt = 0; attempt < 2; ++attempt) {
    try {
      if (!g_pub.connected() && !fresh_bus(g_pub)) continue;
      g_pub.publish(subject, data, "", headers);
      return true;
    } catch (const std::exception&) {
      g_pub.close();  // stale connection: reconnect once
    }
  }
  return false;
}

// ------------------------------------------------------------------- routes

std::pair<int, std::string> route_submit_url(const std::string& body) {
  json::Value j;
  try {
    j = json::parse(body);
  } catch (const std::exception& e) {
    return {400, msg_json(std::string("invalid JSON: ") + e.what())};
  }
  std::string url;
  if (j.has("url") && !j.at("url").is_null()) url = j.at("url").as_string();
  // trim
  size_t b = url.find_first_not_of(" \t\r\n");
  url = b == std::string::npos ? "" : url.substr(b, url.find_last_not_of(" \t\r\n") - b + 1);
  if (url.empty()) return {400, msg_json("URL cannot be empty")};  // main.rs:48-53
  symbiont::PerceiveUrlTask task;
  task.url = url;
  if (!publish_locked(symbiont::subjects::TASKS_PERCEIVE_URL,
                      task.to_json_string(),
                      symbiont::child_headers({})))
    return {500, msg_json("bus publish failed")};
  return {200, msg_json("Task to scrape URL '" + url + "' submitted successfully.")};
}

std::pair<int, std::string> route_generate_text(const std::string& body) {
  symbiont::GenerateTextTask task;
  try {
    task = symbiont::GenerateTextTask::parse(body);
  } catch (const std::exception& e) {
    return {400, msg_json(std::string("invalid JSON: ") + e.what())};
  }
  std::string id = task.task_id;
  size_t b = id.find_first_not_of(" \t\r\n");
  if (b == std::string::npos)
    return {400, msg_json("task_id cannot be empty")};  // main.rs:125-131
  if (task.max_length == 0 || task.max_length > (uint64_t)g_cfg.max_gen_length) {
    json::Value o = json::Value::object();  // main.rs:133-142
    o.set("message", json::Value("max_length must be between 1 and " +
                                 std::to_string(g_cfg.max_gen_length)));
    o.set("task_id", json::Value(task.task_id));
    return {400, o.dump()};
  }
  // sampling overrides (our addition): same bounds as the Python twin
  if (task.temperature && (*task.temperature < 0.0f || *task.temperature > 10.0f)) {
    json::Value o = json::Value::object();
    o.set("message", json::Value("temperature must be between 0 and 10"));
    o.set("task_id", json::Value(task.task_id));
    return {400, o.dump()};
  }
  if (task.top_k && *task.top_k > 100000) {
    json::Value o = json::Value::object();
    o.set("message", json::Value("top_k must be at most 100000"));
    o.set("task_id", json::Value(task.task_id));
    return {400, o.dump()};
  }
  if (!publish_locked(symbiont::subjects::TASKS_GENERATION_TEXT,
                      task.to_json_string(), symbiont::child_headers({})))
    return {500, msg_json("bus publish failed")};
  json::Value o = json::Value::object();
  o.set("message", json::Value("Text generation task (id: " + task.task_id +
                               ") submitted successfully."));
  o.set("task_id", json::Value(task.task_id));
  return {200, o.dump()};
}

// Rerank hop + final 200 — shared tail of the fused and 2-hop search paths.
std::pair<int, std::string> finish_search(
    symbus::Client& bus, const symbiont::SemanticSearchApiRequest& req,
    symbiont::SemanticSearchApiResponse& resp,
    const std::map<std::string, std::string>& trace) {
  if (req.rerank && *req.rerank && !resp.results.empty()) {
    // third hop (our addition, BASELINE.md #4): cross-encoder rerank of the
    // top-k hits through the engine plane; hit scores become CE logits
    json::Value rr_req = json::Value::object();
    rr_req.set("query", json::Value(req.query_text));
    json::Value passages = json::Value::array();
    for (const auto& r : resp.results)
      passages.push_back(json::Value(r.payload.sentence_text));
    rr_req.set("passages", std::move(passages));
    auto reply = bus.request(symbiont::subjects::ENGINE_RERANK, rr_req.dump(),
                             g_cfg.rerank_timeout_ms, trace);
    if (!reply) {
      resp.results.clear();
      resp.error_message =
          "Failed to get rerank scores from engine service: timeout";
      return {503, resp.to_json_string()};
    }
    try {
      json::Value rr = json::parse(reply->data);
      if (rr.has("error_message") && !rr.at("error_message").is_null()) {
        resp.results.clear();
        resp.error_message = rr.at("error_message").as_string();
        return {500, resp.to_json_string()};
      }
      const auto& scores = rr.at("scores").as_array();
      if (scores.size() != resp.results.size())
        throw std::runtime_error("score count mismatch");
      for (size_t i = 0; i < scores.size(); ++i)
        resp.results[i].score = (float)scores[i].as_number();
      std::stable_sort(resp.results.begin(), resp.results.end(),
                       [](const symbiont::SemanticSearchResultItem& a,
                          const symbiont::SemanticSearchResultItem& b) {
                         return a.score > b.score;
                       });
    } catch (const std::exception& e) {
      resp.results.clear();
      resp.error_message = std::string("bad rerank reply: ") + e.what();
      return {500, resp.to_json_string()};
    }
  }
  return {200, resp.to_json_string()};
}

std::pair<int, std::string> route_semantic_search(const std::string& body) {
  // 2-hop orchestration, reference status mapping (main.rs:272-512):
  // hop timeout → 503; service-reported error → 500
  symbiont::SemanticSearchApiRequest req;
  try {
    req = symbiont::SemanticSearchApiRequest::parse(body);
  } catch (const std::exception& e) {
    return {400, msg_json(std::string("invalid JSON: ") + e.what())};
  }
  std::string request_id = symbiont::uuid4();
  auto trace = symbiont::child_headers({});

  symbiont::SemanticSearchApiResponse resp;
  resp.search_request_id = request_id;

  symbus::Client bus;
  if (!fresh_bus(bus)) {
    resp.error_message = "bus unavailable";
    return {503, resp.to_json_string()};
  }

  if (g_cfg.fused_search &&
      req.top_k <= (uint64_t)std::max(0, g_cfg.fused_max_top_k) &&
      steady_now_ms() >= g_fused_down_until_ms.load()) {
    // fused embed+top-k engine hop (pre-warmed for the k<=16 buckets only —
    // a larger k would pay a cold XLA compile inside the probe timeout and
    // trip the negative cache for everyone): one bus hop, one device
    // round-trip; timeout or malformed reply falls back to the 2-hop
    // orchestration
    json::Value fq = json::Value::object();
    fq.set("text", json::Value(req.query_text));
    fq.set("top_k", json::Value((double)req.top_k));
    auto reply = bus.request(symbiont::subjects::ENGINE_QUERY_SEARCH,
                             fq.dump(), g_cfg.fused_timeout_ms, trace);
    if (reply) {
      try {
        json::Value rr = json::parse(reply->data);
        if (rr.has("error_message") && !rr.at("error_message").is_null()) {
          resp.error_message = rr.at("error_message").as_string();
          return {500, resp.to_json_string()};
        }
        std::vector<symbiont::SemanticSearchResultItem> items;
        for (const auto& h : rr.at("hits").as_array()) {
          symbiont::SemanticSearchResultItem item;
          item.qdrant_point_id = h.at("id").as_string();
          item.score = (float)h.at("score").as_number();
          item.payload = symbiont::QdrantPointPayload::from_json(h.at("payload"));
          items.push_back(std::move(item));
        }
        resp.results = std::move(items);
        g_metrics.inc("api.fused_search");
        return finish_search(bus, req, resp, trace);
      } catch (const std::exception& e) {
        symbiont::logline("WARN", SERVICE,
                          std::string("bad fused-search reply (") + e.what() +
                          "); falling back to 2-hop");
        g_metrics.inc("api.fused_search_fallback");
      }
    } else {
      g_fused_down_until_ms.store(steady_now_ms() + g_cfg.fused_down_ms);
      g_metrics.inc("api.fused_search_fallback");
    }
  }

  symbiont::QueryForEmbeddingTask embed_task;
  embed_task.request_id = request_id;
  embed_task.text_to_embed = req.query_text;
  auto reply = bus.request(symbiont::subjects::TASKS_EMBEDDING_FOR_QUERY,
                           embed_task.to_json_string(), g_cfg.embed_timeout_ms,
                           trace);
  if (!reply) {
    resp.error_message =
        "Failed to get embedding from preprocessing service: timeout";
    return {503, resp.to_json_string()};
  }
  symbiont::QueryEmbeddingResult embed_result;
  try {
    embed_result = symbiont::QueryEmbeddingResult::parse(reply->data);
  } catch (const std::exception& e) {
    resp.error_message = std::string("bad embedding reply: ") + e.what();
    return {500, resp.to_json_string()};
  }
  if (embed_result.error_message || !embed_result.embedding) {
    resp.error_message = embed_result.error_message
                             ? *embed_result.error_message
                             : "embedding service returned no embedding";
    return {500, resp.to_json_string()};
  }

  symbiont::SemanticSearchNatsTask search_task;
  search_task.request_id = request_id;
  search_task.query_embedding = *embed_result.embedding;
  search_task.top_k = req.top_k;
  reply = bus.request(symbiont::subjects::TASKS_SEARCH_SEMANTIC_REQUEST,
                      search_task.to_json_string(), g_cfg.search_timeout_ms,
                      trace);
  if (!reply) {
    resp.error_message =
        "Failed to get search results from vector memory service: timeout";
    return {503, resp.to_json_string()};
  }
  symbiont::SemanticSearchNatsResult search_result;
  try {
    search_result = symbiont::SemanticSearchNatsResult::parse(reply->data);
  } catch (const std::exception& e) {
    resp.error_message = std::string("bad search reply: ") + e.what();
    return {500, resp.to_json_string()};
  }
  if (search_result.error_message) {
    resp.error_message = *search_result.error_message;
    return {500, resp.to_json_string()};
  }
  resp.results = std::move(search_result.results);
  return finish_search(bus, req, resp, trace);
}

std::string health_err_json(const std::string& message) {
  json::Value o = json::Value::object();  // proper escaping for any message
  o.set("ok", json::Value(false));
  o.set("error_message", json::Value(message));
  return o.dump();
}

std::pair<int, std::string> route_engine_health() {
  // engine-plane health over HTTP (Python-twin parity): one bus round-trip
  // to engine.health; 503 when no engine plane answers
  symbus::Client bus;
  if (!fresh_bus(bus)) return {503, health_err_json("bus unavailable")};
  auto reply = bus.request(symbiont::subjects::ENGINE_HEALTH, "{}",
                           g_cfg.health_timeout_ms,
                           symbiont::child_headers({}));
  if (!reply) return {503, health_err_json("engine plane unreachable")};
  try {
    json::Value v = json::parse(reply->data);
    if (!v.is_object()) throw std::runtime_error("not an object");
    if (v.has("error_message") && !v.at("error_message").is_null()) {
      // the health op itself failed — surface as unhealthy, not 200
      if (!v.has("ok")) v.set("ok", json::Value(false));
      return {500, v.dump()};
    }
    return {200, v.dump()};
  } catch (const std::exception& e) {
    return {500, health_err_json(std::string("bad engine health reply: ")
                                 + e.what())};
  }
}

// --------------------------------------------------------------------- sse

// percent-decode for query values (task ids are uuids, but a strict client
// may still escape; '+' is a space per application/x-www-form-urlencoded)
std::string url_decode(const std::string& s) {
  std::string out;
  out.reserve(s.size());
  for (size_t i = 0; i < s.size(); ++i) {
    if (s[i] == '+') {
      out += ' ';
    } else if (s[i] == '%' && i + 2 < s.size() &&
               std::isxdigit((unsigned char)s[i + 1]) &&
               std::isxdigit((unsigned char)s[i + 2])) {
      out += (char)std::stoi(s.substr(i + 1, 2), nullptr, 16);
      i += 2;
    } else {
      out += s[i];
    }
  }
  return out;
}

std::string query_param(const std::string& query, const std::string& key) {
  size_t pos = 0;
  while (pos < query.size()) {
    size_t amp = query.find('&', pos);
    std::string pair = query.substr(
        pos, amp == std::string::npos ? std::string::npos : amp - pos);
    auto eq = pair.find('=');
    if (eq != std::string::npos && url_decode(pair.substr(0, eq)) == key)
      return url_decode(pair.substr(eq + 1));
    if (amp == std::string::npos) break;
    pos = amp + 1;
  }
  return "";
}

void serve_sse(int fd, const HttpRequest& req) {
  std::string head =
      "HTTP/1.1 200 OK\r\nContent-Type: text/event-stream\r\n"
      "Cache-Control: no-cache\r\n" +
      cors_headers(req.headers) + "Connection: keep-alive\r\n\r\n";
  if (!send_all(fd, head)) return;
  // ?task_id=<id> opts into per-task routing (see SseHub)
  auto q = g_hub.register_client(query_param(req.query, "task_id"));
  g_metrics.inc("api.sse_clients");
  for (;;) {
    std::string payload;
    bool have = false;
    {
      std::unique_lock<std::mutex> lk(q->mu);
      q->cv.wait_for(lk, std::chrono::milliseconds(g_cfg.sse_keepalive_ms),
                     [&] { return !q->items.empty() || q->closed; });
      if (q->closed) break;
      if (!q->items.empty()) {
        payload = std::move(q->items.front());
        q->items.pop_front();
        have = true;
      }
    }
    std::string frame;
    if (have) {
      // multi-line payloads become multiple data: lines (SSE framing)
      size_t start = 0;
      while (start <= payload.size()) {
        size_t eol = payload.find('\n', start);
        std::string line = eol == std::string::npos
                               ? payload.substr(start)
                               : payload.substr(start, eol - start);
        if (!line.empty() && line.back() == '\r') line.pop_back();
        frame += "data: " + line + "\n";
        if (eol == std::string::npos) break;
        start = eol + 1;
      }
      frame += "\n";
    } else {
      frame = ": keep-alive\n\n";
    }
    if (!send_all(fd, frame)) break;
  }
  g_hub.unregister(q);
}

// bridge thread: owns the events.text.generated(.partial) subscriptions
// (reference: nats_to_sse_listener, main.rs:215-270; streaming deltas are
// this framework's addition and ride the same SSE channel)
void sse_bridge() {
  // fleet liveness rides the bridge's bus client: the supervisor's hang
  // detector (and the /api/fleet roll-up) covers the C++ gateway exactly
  // like the Python runners (SYMBIONT_RUNNER_HEARTBEAT_S > 0)
  symbiont::Heartbeat hb = symbiont::heartbeat_from_env(SERVICE);
  for (;;) {
    symbus::Client bus;
    if (!symbiont::connect_with_retry(bus, SERVICE)) return;
    bus.subscribe(symbiont::subjects::EVENTS_TEXT_GENERATED);
    bus.subscribe(symbiont::subjects::EVENTS_TEXT_GENERATED_PARTIAL);
    g_ready.store(true);  // bus live + subscribed: safe to take data paths
    while (bus.connected()) {
      auto msg = bus.next(1000);
      symbiont::maybe_heartbeat(bus, hb);
      if (!msg) continue;
      g_hub.broadcast(msg->data, g_cfg.sse_capacity);
      g_metrics.inc("api.sse_broadcast");
    }
    // readiness is a LIVE claim: with the bus gone, /readyz must go 503
    // and the data-path gate must re-engage — a gateway that keeps
    // advertising ready while its bridge redials (or gives up after the
    // retry budget) is serving into nothing, the exact window the
    // liveness/readiness split exists to close
    g_ready.store(false);
    symbiont::logline("WARN", SERVICE, "sse bridge lost bus; reconnecting");
  }
}

// ------------------------------------------------------------------- server

void handle_connection(int fd) {
  for (;;) {
    HttpRequest req;
    int err_status = 0;
    if (!read_http_request(fd, req, 30000, &err_status)) {
      if (err_status) {
        // Python-twin parity: a bad/oversized Content-Length gets a status,
        // not a dropped socket; drain (bounded) so the close doesn't RST
        // the queued response away from a mid-upload client
        const char* msg = err_status == 413 ? "request body exceeds 16MB limit"
                                            : "invalid Content-Length";
        write_response(fd, err_status,
                       std::string("{\"status\":\"error\",\"message\":\"") +
                           msg + "\"}",
                       req.headers, false);
        char sink[16384];
        int64_t drain_deadline = (int64_t)symbiont::now_ms() + 1000;
        for (int i = 0; i < 64; ++i) {
          int wait = (int)(drain_deadline - (int64_t)symbiont::now_ms());
          if (wait <= 0) break;
          struct pollfd p {fd, POLLIN, 0};
          if (::poll(&p, 1, wait) <= 0) break;
          if (::recv(fd, sink, sizeof(sink), 0) <= 0) break;
        }
      }
      break;
    }
    bool keep_alive = true;
    auto conn = req.headers.find("connection");
    if (conn != req.headers.end()) {
      std::string v = conn->second;
      for (auto& c : v) c = (char)std::tolower((unsigned char)c);
      keep_alive = v != "close";
    }
    if (req.method == "GET" && req.path == "/api/events") {
      serve_sse(fd, req);  // SSE occupies the connection
      break;
    }
    if (req.method == "GET" && (req.path == "/" || req.path == "/index.html") &&
        !g_frontend_html.empty()) {
      std::string head =
          "HTTP/1.1 200 OK\r\nContent-Type: text/html; charset=utf-8\r\n"
          "Content-Length: " + std::to_string(g_frontend_html.size()) + "\r\n" +
          cors_headers(req.headers) +
          (keep_alive ? "Connection: keep-alive\r\n\r\n"
                      : "Connection: close\r\n\r\n");
      if (!send_all(fd, head + g_frontend_html) || !keep_alive) break;
      continue;
    }
    int status = 404;
    std::string body;
    if (req.method == "POST" && !g_ready.load() &&
        (req.path == "/api/submit-url" || req.path == "/api/generate-text" ||
         req.path == "/api/search/semantic")) {
      // Python-twin parity (api.py _route): a cold gateway must refuse
      // data-path work honestly instead of 200ing into a bus with no
      // connection — a well-behaved LB watches /readyz and never sends this
      g_metrics.inc("api.not_ready_rejects");
      write_response(fd, 503,
                     msg_json("stack is warming up (see /readyz)"),
                     req.headers, keep_alive);
      if (!keep_alive) break;
      continue;
    }
    if (req.method == "POST" &&
        (req.path == "/api/submit-url" || req.path == "/api/generate-text" ||
         req.path == "/api/search/semantic")) {
      // per-tenant quota check (Python _edge_admit parity): an exhausted
      // bucket answers 429 + Retry-After at the edge — never an unbounded
      // queue, and never a bus publish for work nobody can absorb
      using Gate = symbiont::AdmissionGate;
      Gate::Class klass = req.path == "/api/submit-url" ? Gate::INGEST
                          : req.path == "/api/generate-text"
                              ? Gate::GENERATE
                              : Gate::SEARCH;
      const char* cls_name = klass == Gate::INGEST     ? "ingest"
                             : klass == Gate::GENERATE ? "generate"
                                                       : "search";
      std::string tenant = symbiont::http_tenant_of(req.headers);
      double retry_after_s = 1.0;
      if (!g_admission.admit(klass, tenant, &retry_after_s)) {
        g_metrics.inc(std::string("admission.throttled.") + cls_name);
        json::Value o = json::Value::object();
        o.set("message", json::Value("tenant '" + tenant + "' over its " +
                                     cls_name + " quota"));
        o.set("reason", json::Value("quota"));
        o.set("task_id", json::Value());
        long retry = (long)retry_after_s + 1;  // ceil-ish, minimum 1
        write_response(fd, 429, o.dump(), req.headers, keep_alive,
                       "Retry-After: " + std::to_string(retry) + "\r\n");
        if (!keep_alive) break;
        continue;
      }
      g_metrics.inc(std::string("admission.admitted.") + cls_name);
    }
    if (req.method == "OPTIONS") {
      status = 200;
      body = "";
    } else if (req.method == "POST" && req.path == "/api/submit-url") {
      g_metrics.inc("api.POST./api/submit-url");
      std::tie(status, body) = route_submit_url(req.body);
    } else if (req.method == "POST" && req.path == "/api/generate-text") {
      g_metrics.inc("api.POST./api/generate-text");
      std::tie(status, body) = route_generate_text(req.body);
    } else if (req.method == "POST" && req.path == "/api/search/semantic") {
      g_metrics.inc("api.POST./api/search/semantic");
      std::tie(status, body) = route_semantic_search(req.body);
    } else if (req.method == "GET" && req.path == "/api/metrics") {
      status = 200;
      body = g_metrics.snapshot_json();
    } else if (req.method == "GET" && req.path == "/healthz") {
      // liveness ONLY: the process is up and serving HTTP. Routing
      // decisions belong to /readyz (Python-twin split).
      status = 200;
      body = "{\"status\": \"ok\"}";
    } else if (req.method == "GET" && req.path == "/readyz") {
      if (g_ready.load()) {
        status = 200;
        body = "{\"status\": \"ready\"}";
      } else {
        status = 503;
        body = "{\"status\": \"starting\", \"message\": "
               "\"bus connection in progress\"}";
      }
    } else if (req.method == "GET" && req.path == "/api/health/engine") {
      std::tie(status, body) = route_engine_health();
    } else {
      g_metrics.inc("api.unmatched");
      body = msg_json("not found");
    }
    write_response(fd, status, body, req.headers, keep_alive);
    if (!keep_alive) break;
  }
  ::close(fd);
}

}  // namespace

int main() {
  ::signal(SIGPIPE, SIG_IGN);
  g_cfg.host = symbiont::env_or("SYMBIONT_API_HOST",
                                symbiont::env_or("API_SERVER_HOST", "127.0.0.1"));
  g_cfg.port = std::atoi(symbiont::env_or(
      "SYMBIONT_API_PORT", symbiont::env_or("API_SERVER_PORT", "8080")).c_str());
  g_cfg.max_gen_length =
      std::atoi(symbiont::env_or("SYMBIONT_API_MAX_GEN_LENGTH", "1000").c_str());
  g_cfg.sse_keepalive_ms = (int)(1000 * std::atof(
      symbiont::env_or("SYMBIONT_API_SSE_KEEPALIVE_S", "15").c_str()));
  g_cfg.sse_capacity = (size_t)std::atoi(
      symbiont::env_or("SYMBIONT_API_SSE_CHANNEL_CAPACITY", "32").c_str());
  g_cfg.embed_timeout_ms = (int)(1000 * std::atof(
      symbiont::env_or("SYMBIONT_BUS_REQUEST_TIMEOUT_EMBED_S", "15").c_str()));
  g_cfg.search_timeout_ms = (int)(1000 * std::atof(
      symbiont::env_or("SYMBIONT_BUS_REQUEST_TIMEOUT_SEARCH_S", "20").c_str()));
  g_cfg.rerank_timeout_ms = (int)(1000 * std::atof(
      symbiont::env_or("SYMBIONT_BUS_REQUEST_TIMEOUT_RERANK_S", "10").c_str()));
  g_cfg.health_timeout_ms = (int)(1000 * std::atof(
      symbiont::env_or("SYMBIONT_BUS_REQUEST_TIMEOUT_HEALTH_S", "5").c_str()));
  {
    std::string fused = symbiont::env_or("SYMBIONT_API_FUSED_SEARCH", "true");
    g_cfg.fused_search = (fused != "false" && fused != "0" && fused != "no");
  }
  g_cfg.fused_timeout_ms = (int)(1000 * std::atof(
      symbiont::env_or("SYMBIONT_API_FUSED_SEARCH_TIMEOUT_S", "5").c_str()));
  g_cfg.fused_down_ms = (int)(1000 * std::atof(
      symbiont::env_or("SYMBIONT_API_FUSED_SEARCH_DOWN_S", "60").c_str()));
  g_cfg.fused_max_top_k = std::atoi(
      symbiont::env_or("SYMBIONT_API_FUSED_SEARCH_MAX_TOP_K", "16").c_str());
  g_admission.configure();  // SYMBIONT_ADMISSION_* (docs/RESILIENCE.md)

  int lfd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (lfd < 0) return 1;
  int one = 1;
  ::setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  struct sockaddr_in addr {};
  addr.sin_family = AF_INET;
  addr.sin_port = htons((uint16_t)g_cfg.port);
  if (::inet_pton(AF_INET, g_cfg.host.c_str(), &addr.sin_addr) != 1)
    addr.sin_addr.s_addr = INADDR_ANY;
  if (::bind(lfd, (struct sockaddr*)&addr, sizeof(addr)) != 0) {
    symbiont::logline("ERROR", SERVICE, "bind failed on port " +
                                            std::to_string(g_cfg.port));
    return 1;
  }
  if (::listen(lfd, 128) != 0) return 1;

  load_frontend();
  std::thread(sse_bridge).detach();
  symbiont::logline("INFO", SERVICE,
                    "ready: listening on " + g_cfg.host + ":" +
                        std::to_string(g_cfg.port));

  for (;;) {
    int cfd = ::accept(lfd, nullptr, nullptr);
    if (cfd < 0) {
      if (errno == EINTR) continue;
      break;
    }
    ::setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    std::thread(handle_connection, cfd).detach();
  }
  return 0;
}
