// symbus C++ client — the bus face of every native worker shell.
//
// The reference's workers each hold one async-nats connection and run a
// subscriber loop (reference: services/perception_service/src/main.rs:172-247).
// This client gives the C++ services the same shape without an async runtime:
// one TCP connection, a poll()-driven frame pump, and a FIFO of decoded
// messages; next(timeout) is the `while let Some(msg) = sub.next().await` loop.
// Request-reply mirrors the NATS inbox pattern the reference relies on
// (reference: services/api_service/src/main.rs:309-316): subscribe a unique
// _INBOX subject, publish with reply, wait for the inbox message while other
// traffic keeps queueing.
//
// Thread model: NOT thread-safe by design — one Client per service loop
// (single-owner, like the reference's per-service connection). Services that
// want concurrency run multiple processes under a queue group.
#pragma once

#include <arpa/inet.h>
#include <netdb.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <chrono>
#include <cstdint>
#include <deque>
#include <map>
#include <optional>
#include <random>
#include <stdexcept>
#include <string>

#include "protocol.hpp"

namespace symbus {

struct BusMsg {
  uint32_t sid = 0;
  std::string subject;
  std::string reply;
  std::map<std::string, std::string> headers;
  std::string data;
};

class Client {
 public:
  Client() = default;
  ~Client() { close(); }
  Client(const Client&) = delete;
  Client& operator=(const Client&) = delete;

  void connect(const std::string& host, int port) {
    struct addrinfo hints {};
    hints.ai_family = AF_UNSPEC;
    hints.ai_socktype = SOCK_STREAM;
    struct addrinfo* res = nullptr;
    std::string ports = std::to_string(port);
    int rc = ::getaddrinfo(host.c_str(), ports.c_str(), &hints, &res);
    if (rc != 0) throw std::runtime_error("resolve " + host + ": " + gai_strerror(rc));
    int fd = -1;
    for (auto* ai = res; ai; ai = ai->ai_next) {
      fd = ::socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
      if (fd < 0) continue;
      if (::connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) break;
      ::close(fd);
      fd = -1;
    }
    ::freeaddrinfo(res);
    if (fd < 0) throw std::runtime_error("connect " + host + ":" + ports + " failed");
    int one = 1;
    ::setsockopt(fd, IPPROTO_TCP, 1 /*TCP_NODELAY*/, &one, sizeof(one));
    fd_ = fd;
  }

  bool connected() const { return fd_ >= 0; }

  void close() {
    if (fd_ >= 0) {
      ::close(fd_);
      fd_ = -1;
    }
  }

  uint32_t subscribe(const std::string& subject, const std::string& queue = "") {
    uint32_t sid = next_sid_++;
    Writer w;
    w.u8(OP_SUB);
    w.u32(sid);
    w.str(subject);
    w.str(queue);
    send_frame(w);
    return sid;
  }

  void unsubscribe(uint32_t sid) {
    Writer w;
    w.u8(OP_UNSUB);
    w.u32(sid);
    send_frame(w);
  }

  void publish(const std::string& subject, const std::string& data,
               const std::string& reply = "",
               const std::map<std::string, std::string>& headers = {}) {
    Writer w;
    w.u8(OP_PUB);
    w.str(subject);
    w.str(reply);
    w.u16((uint16_t)headers.size());
    for (const auto& [k, v] : headers) {
      w.str(k);
      w.str(v);
    }
    w.data(data);
    send_frame(w);
  }

  // Next queued message from any subscription. timeout_ms < 0 blocks forever.
  std::optional<BusMsg> next(int timeout_ms) {
    auto deadline = now_ms() + timeout_ms;
    for (;;) {
      if (!inbox_.empty()) {
        BusMsg m = std::move(inbox_.front());
        inbox_.pop_front();
        return m;
      }
      int wait = timeout_ms < 0 ? -1 : (int)(deadline - now_ms());
      if (timeout_ms >= 0 && wait <= 0) return std::nullopt;
      if (!pump(wait)) return std::nullopt;  // timed out (or closed)
    }
  }

  // Inbox request-reply (reference: api_service/src/main.rs:309-316 pattern).
  // Messages for other subscriptions arriving meanwhile stay queued for next().
  std::optional<BusMsg> request(const std::string& subject, const std::string& data,
                                int timeout_ms,
                                const std::map<std::string, std::string>& headers = {}) {
    std::string inbox = "_INBOX." + random_token();
    uint32_t sid = subscribe(inbox, "");
    publish(subject, data, inbox, headers);
    auto deadline = now_ms() + timeout_ms;
    std::optional<BusMsg> out;
    for (;;) {
      // scan queued messages for the reply
      for (auto it = inbox_.begin(); it != inbox_.end(); ++it) {
        if (it->sid == sid) {
          out = std::move(*it);
          inbox_.erase(it);
          break;
        }
      }
      if (out) break;
      int wait = (int)(deadline - now_ms());
      if (wait <= 0 || !pump(wait)) break;
    }
    try {
      unsubscribe(sid);
    } catch (const std::exception&) {
      // connection dropped mid-request: the timeout/nullopt result already
      // reports the failure; throwing here would escape into caller threads
    }
    return out;
  }

  void ping() {
    Writer w;
    w.u8(OP_PING);
    send_frame(w);
  }

  // ---- durable streams (broker: streams.hpp; control rides reserved
  // request-reply subjects, so no extra opcodes) ----------------------------

  // Control replies may be compact ({"ok":true}) or spaced ({"ok": true})
  // depending on which broker path serialized them.
  static bool reply_ok(const std::string& data) {
    auto k = data.find("\"ok\"");
    if (k == std::string::npos) return false;
    auto p = data.find_first_not_of(": \t", k + 4);
    return p != std::string::npos && data.compare(p, 4, "true") == 0;
  }

  // Create/refresh a stream capturing `subjects`. Throws on broker error.
  void add_stream(const std::string& name,
                  const std::vector<std::string>& subjects,
                  int64_t ack_wait_ms = 30000, uint32_t max_deliver = 5,
                  int timeout_ms = 10000) {
    std::string req = "{\"stream\": \"" + name + "\", \"subjects\": [";
    for (size_t i = 0; i < subjects.size(); ++i) {
      if (i) req += ", ";
      req += "\"" + subjects[i] + "\"";
    }
    req += "], \"ack_wait_ms\": " + std::to_string(ack_wait_ms) +
           ", \"max_deliver\": " + std::to_string(max_deliver) + "}";
    auto r = request("_SYMBUS.stream.create", req, timeout_ms);
    if (!r || !reply_ok(r->data))
      throw std::runtime_error("stream create failed: " +
                               (r ? r->data : "timeout"));
  }

  // Join durable consumer group `group` on `stream`; deliveries arrive via
  // next() on the returned sid with X-Symbus-* headers. Ack with ack(msg)
  // after the side effect is durable, else the message redelivers.
  uint32_t durable_subscribe(const std::string& stream, const std::string& group,
                             const std::string& filter_subject = "",
                             int timeout_ms = 10000) {
    uint32_t sid = subscribe("_SYMBUS.deliver." + stream + "." + group, group);
    std::string req =
        "{\"stream\": \"" + stream + "\", \"group\": \"" + group + "\"" +
        (filter_subject.empty()
             ? std::string()
             : ", \"filter_subject\": \"" + filter_subject + "\"") +
        "}";
    auto r = request("_SYMBUS.consumer.create", req, timeout_ms);
    if (!r || !reply_ok(r->data))
      throw std::runtime_error("consumer create failed: " +
                               (r ? r->data : "timeout"));
    return sid;
  }

  void ack(const BusMsg& m) {
    auto s = m.headers.find("X-Symbus-Stream");
    auto g = m.headers.find("X-Symbus-Group");
    auto q = m.headers.find("X-Symbus-Seq");
    if (s == m.headers.end() || g == m.headers.end() || q == m.headers.end())
      return;  // not a durable delivery
    publish("_SYMBUS.ack", "{\"stream\": \"" + s->second + "\", \"group\": \"" +
                               g->second + "\", \"seq\": " + q->second + "}");
  }

  static std::string random_token() {
    static thread_local std::mt19937_64 rng{std::random_device{}()};
    static const char* hex = "0123456789abcdef";
    std::string s(24, '0');
    for (auto& c : s) c = hex[rng() & 15];
    return s;
  }

 private:
  static int64_t now_ms() {
    return std::chrono::duration_cast<std::chrono::milliseconds>(
               std::chrono::steady_clock::now().time_since_epoch())
        .count();
  }

  void send_frame(const Writer& w) {
    if (fd_ < 0) throw std::runtime_error("symbus client not connected");
    std::string f = w.frame();
    size_t off = 0;
    while (off < f.size()) {
      ssize_t n = ::send(fd_, f.data() + off, f.size() - off, 0);
      if (n <= 0) {
        close();
        throw std::runtime_error("symbus send failed");
      }
      off += (size_t)n;
    }
  }

  // Read until at least one full frame is decoded or the timeout passes.
  // Returns false on timeout or connection close.
  bool pump(int timeout_ms) {
    if (fd_ < 0) return false;
    auto deadline = timeout_ms < 0 ? INT64_MAX : now_ms() + timeout_ms;
    size_t had = inbox_.size();
    for (;;) {
      // decode any complete frames already buffered
      while (try_decode_frame()) {
      }
      if (inbox_.size() > had) return true;
      int wait = timeout_ms < 0 ? -1 : (int)(deadline - now_ms());
      if (timeout_ms >= 0 && wait <= 0) return false;
      struct pollfd p {fd_, POLLIN, 0};
      int rc = ::poll(&p, 1, wait);
      if (rc == 0) return false;
      if (rc < 0) {
        if (errno == EINTR) continue;
        close();
        return false;
      }
      char buf[65536];
      ssize_t n = ::recv(fd_, buf, sizeof(buf), 0);
      if (n <= 0) {
        close();
        return false;
      }
      rxbuf_.append(buf, (size_t)n);
    }
  }

  bool try_decode_frame() {
    if (rxbuf_.size() < 4) return false;
    uint32_t len = 0;
    for (int i = 0; i < 4; ++i) len |= ((uint32_t)(uint8_t)rxbuf_[i]) << (8 * i);
    if (len == 0 || len > MAX_FRAME) throw std::runtime_error("bad frame length");
    if (rxbuf_.size() < 4 + (size_t)len) return false;
    Reader r(rxbuf_.data() + 4, len);
    uint8_t op = r.u8();
    if (op == OP_MSG) {
      BusMsg m;
      m.sid = r.u32();
      m.subject = r.str();
      m.reply = r.str();
      uint16_t nh = r.u16();
      for (uint16_t i = 0; i < nh; ++i) {
        std::string k = r.str();
        m.headers[k] = r.str();
      }
      m.data = r.data();
      inbox_.push_back(std::move(m));
    } else if (op == OP_ERR) {
      last_error_ = r.str();
    }  // OP_PONG: frame consumed, nothing queued
    rxbuf_.erase(0, 4 + (size_t)len);
    return true;
  }

  int fd_ = -1;
  uint32_t next_sid_ = 1;
  std::string rxbuf_;
  std::deque<BusMsg> inbox_;
  std::string last_error_;
};

}  // namespace symbus
