// symbus durable streams — the JetStream-equivalent layer SURVEY.md §5.3
// calls for. The reference runs core NATS: at-most-once, a crashed consumer
// silently loses in-flight work (SURVEY.md §1-L3 notes). Here:
//
// - a STREAM captures every publish matching its subject set into an
//   append-only log (optionally persisted to --data-dir, replayed on boot);
// - a durable CONSUMER GROUP gets deliveries pushed to
//   `_SYMBUS.deliver.<stream>.<group>` — clients subscribe that subject under
//   queue group <group>, so replicas share the work exactly like plain
//   queue-group subscribers;
// - messages carry X-Symbus-Stream/-Seq/-Subject/-Deliveries headers; the
//   client acks by publishing to `_SYMBUS.ack`; unacked messages redeliver
//   after ack_wait up to max_deliver attempts (then count as dead-lettered);
// - everything rides the existing wire protocol: the control surface is three
//   reserved request-reply subjects (`_SYMBUS.stream.create`,
//   `_SYMBUS.consumer.create`, `_SYMBUS.ack`), so clients in any language
//   get durability with zero new opcodes.
//
// The engine-restart story this enables (SURVEY.md §7 hard part #6):
// vector_memory acks only after the engine confirms the upsert, so an engine
// or worker crash between delivery and durable write redelivers the document
// instead of losing it.
#pragma once

#include <dirent.h>

#include <algorithm>
#include <cerrno>
#include <cstdio>
#include <cstring>
#include <ctime>
#include <functional>
#include <map>
#include <set>
#include <string>
#include <vector>

#include "../json.hpp"
#include "protocol.hpp"

namespace symbus {

inline int64_t steady_ms() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (int64_t)ts.tv_sec * 1000 + ts.tv_nsec / 1000000;
}

using HeaderList = std::vector<std::pair<std::string, std::string>>;

struct StreamMsg {
  uint64_t seq;
  std::string subject;
  HeaderList headers;
  std::string data;
};

struct InFlight {
  int64_t deadline_ms;
  uint32_t deliveries;
};

struct ConsumerGroup {
  std::string name;
  std::string filter;  // subject pattern; empty = whole stream
  uint64_t ack_floor = 0;            // all seq <= floor are acked
  std::set<uint64_t> acked;          // acked above the floor
  std::map<uint64_t, InFlight> inflight;
  std::map<uint64_t, uint32_t> redeliveries;  // seq -> past delivery count
  uint64_t next_seq = 1;             // next never-delivered seq
  uint64_t dead_lettered = 0;

  bool is_acked(uint64_t seq) const {
    return seq <= ack_floor || acked.count(seq);
  }

  void ack(uint64_t seq) {
    inflight.erase(seq);
    redeliveries.erase(seq);
    if (seq <= ack_floor) return;
    acked.insert(seq);
    while (acked.count(ack_floor + 1)) {
      acked.erase(ack_floor + 1);
      ack_floor++;
    }
  }
};

struct Stream {
  std::string name;
  std::vector<std::string> subjects;
  int64_t ack_wait_ms = 30000;
  uint32_t max_deliver = 5;
  uint64_t last_seq = 0;
  std::map<uint64_t, StreamMsg> msgs;
  std::map<std::string, ConsumerGroup> groups;
  FILE* log = nullptr;

  bool captures(const std::string& subject) const {
    for (const auto& pat : subjects)
      if (subject_matches(pat, subject)) return true;
    return false;
  }
};

// log record types (length-prefixed frames, same framing as the wire)
enum StreamRec : uint8_t {
  REC_META = 0,   // json meta (subjects, ack_wait_ms, max_deliver)
  REC_MSG = 1,    // u64 seq | str subject | u16 nh | (str,str)* | data
  REC_ACK = 2,    // str group | u64 seq
  REC_GROUP = 3,  // str group | u64 ack_floor | u32 n | u64*n acked>floor
                  // (written only by compaction: snapshots group state so a
                  // compacted log needs no per-ack history)
};

class StreamEngine {
 public:
  // deliver(subject, headers, data): routes one frame through the broker
  using DeliverFn =
      std::function<int(const std::string&, const HeaderList&, const std::string&)>;

  void configure(const std::string& data_dir, DeliverFn deliver) {
    data_dir_ = data_dir;
    deliver_ = std::move(deliver);
    if (!data_dir_.empty()) replay_all();
  }

  // ---- control handlers (return reply JSON) -------------------------------

  std::string handle_stream_create(const std::string& body) {
    json::Value j = json::parse(body);
    std::string name = j.at("stream").as_string();
    if (name.empty() || name.find('/') != std::string::npos ||
        name.find("..") != std::string::npos)
      return err_json("bad stream name");
    Stream& s = streams_[name];
    bool fresh = s.name.empty();
    s.name = name;
    s.subjects.clear();
    for (const auto& v : j.at("subjects").as_array())
      s.subjects.push_back(v.as_string());
    if (j.has("ack_wait_ms")) s.ack_wait_ms = (int64_t)j.at("ack_wait_ms").as_number();
    if (j.has("max_deliver")) s.max_deliver = (uint32_t)j.at("max_deliver").as_number();
    if (fresh && !data_dir_.empty()) {
      open_log(s, /*truncate=*/false);
      if (!s.log) {
        // refuse to pretend durability we can't provide
        streams_.erase(name);
        return err_json("cannot persist stream " + name + " in " + data_dir_);
      }
    }
    if (s.log) append_meta(s);
    json::Value r = json::Value::object();
    r.set("ok", json::Value(true));
    r.set("last_seq", json::Value((double)s.last_seq));
    return r.dump();
  }

  std::string handle_consumer_create(const std::string& body) {
    json::Value j = json::parse(body);
    std::string sname = j.at("stream").as_string();
    std::string gname = j.at("group").as_string();
    auto it = streams_.find(sname);
    if (it == streams_.end()) return err_json("unknown stream " + sname);
    ConsumerGroup& g = it->second.groups[gname];
    if (g.name.empty()) g.name = gname;
    if (j.has("filter_subject") && !j.at("filter_subject").is_null())
      g.filter = j.at("filter_subject").as_string();
    json::Value r = json::Value::object();
    r.set("ok", json::Value(true));
    r.set("ack_floor", json::Value((double)g.ack_floor));
    return r.dump();
  }

  std::string handle_ack(const std::string& body) {
    json::Value j = json::parse(body);
    std::string sname = j.at("stream").as_string();
    std::string gname = j.at("group").as_string();
    uint64_t seq = (uint64_t)j.at("seq").as_number();
    auto it = streams_.find(sname);
    if (it == streams_.end()) return err_json("unknown stream " + sname);
    auto git = it->second.groups.find(gname);
    if (git == it->second.groups.end()) return err_json("unknown group " + gname);
    git->second.ack(seq);
    if (it->second.log) append_ack(it->second, gname, seq);
    maybe_gc(it->second);
    return "{\"ok\": true}";
  }

  // ---- capture on publish -------------------------------------------------

  void capture(const std::string& subject, const HeaderList& headers,
               const std::string& data) {
    for (auto& [name, s] : streams_) {
      if (!s.captures(subject)) continue;
      uint64_t seq = ++s.last_seq;
      s.msgs[seq] = StreamMsg{seq, subject, headers, data};
      if (s.log) append_msg(s, s.msgs[seq]);
    }
  }

  // ---- delivery pump (called periodically from the broker's timer) --------

  void pump() {
    int64_t now = steady_ms();
    for (auto& [name, s] : streams_) {
      for (auto& [gname, g] : s.groups) {
        // redeliver expired in-flight
        for (auto it = g.inflight.begin(); it != g.inflight.end();) {
          if (it->second.deadline_ms > now) {
            ++it;
            continue;
          }
          uint64_t seq = it->first;
          uint32_t deliveries = it->second.deliveries;
          it = g.inflight.erase(it);
          if (deliveries >= s.max_deliver) {
            g.dead_lettered++;
            g.ack(seq);  // drop: counted, no longer retried
            // persist like a client ack, else the poison message comes back
            // with a fresh delivery budget after every broker restart
            if (s.log) append_ack(s, gname, seq);
            maybe_gc(s);
            continue;
          }
          g.redeliveries[seq] = deliveries;
        }
        // (re)deliver up to the in-flight window
        while (g.inflight.size() < kMaxInFlight) {
          uint64_t seq = 0;
          uint32_t past = 0;
          if (!g.redeliveries.empty()) {
            seq = g.redeliveries.begin()->first;
            past = g.redeliveries.begin()->second;
            g.redeliveries.erase(g.redeliveries.begin());
          } else {
            // advance past acked seqs AND seqs outside the group's subject
            // filter (auto-acked so the floor keeps moving and gc works)
            for (;;) {
              while (g.next_seq <= s.last_seq && g.is_acked(g.next_seq))
                g.next_seq++;
              if (g.next_seq > s.last_seq) break;
              if (!g.filter.empty()) {
                auto fit = s.msgs.find(g.next_seq);
                if (fit != s.msgs.end() &&
                    !subject_matches(g.filter, fit->second.subject)) {
                  g.ack(g.next_seq);
                  continue;
                }
              }
              break;
            }
            if (g.next_seq > s.last_seq) break;
            seq = g.next_seq++;
          }
          auto mit = s.msgs.find(seq);
          if (mit == s.msgs.end()) continue;  // gc'd (already acked)
          HeaderList h = mit->second.headers;
          h.emplace_back("X-Symbus-Stream", s.name);
          h.emplace_back("X-Symbus-Group", gname);
          h.emplace_back("X-Symbus-Seq", std::to_string(seq));
          h.emplace_back("X-Symbus-Subject", mit->second.subject);
          h.emplace_back("X-Symbus-Deliveries", std::to_string(past + 1));
          int targets = deliver_("_SYMBUS.deliver." + s.name + "." + gname, h,
                                 mit->second.data);
          if (targets == 0) {
            // nobody listening: put it back and stop pushing this group
            g.redeliveries[seq] = past;
            break;
          }
          g.inflight[seq] = InFlight{now + s.ack_wait_ms, past + 1};
        }
      }
    }
  }

  std::string stats_json() {
    json::Value o = json::Value::object();
    for (auto& [name, s] : streams_) {
      json::Value sv = json::Value::object();
      sv.set("last_seq", json::Value((double)s.last_seq));
      sv.set("stored", json::Value((double)s.msgs.size()));
      json::Value gv = json::Value::object();
      for (auto& [gname, g] : s.groups) {
        json::Value one = json::Value::object();
        one.set("ack_floor", json::Value((double)g.ack_floor));
        one.set("inflight", json::Value((double)g.inflight.size()));
        one.set("dead_lettered", json::Value((double)g.dead_lettered));
        gv.set(gname, std::move(one));
      }
      sv.set("groups", std::move(gv));
      o.set(name, std::move(sv));
    }
    return o.dump();
  }

 private:
  static constexpr size_t kMaxInFlight = 64;

  static std::string err_json(const std::string& m) {
    json::Value o = json::Value::object();
    o.set("ok", json::Value(false));
    o.set("error", json::Value(m));
    return o.dump();
  }

  // gc: drop messages acked by EVERY group (bounded memory/log growth is the
  // log's job via restart compaction; in-memory map trims eagerly)
  void maybe_gc(Stream& s) {
    if (s.groups.empty()) return;
    uint64_t floor = UINT64_MAX;
    for (auto& [n, g] : s.groups) floor = std::min(floor, g.ack_floor);
    while (!s.msgs.empty() && s.msgs.begin()->first <= floor)
      s.msgs.erase(s.msgs.begin());
  }

  // ---- persistence --------------------------------------------------------

  std::string log_path(const std::string& name) const {
    return data_dir_ + "/" + name + ".symlog";
  }

  void open_log(Stream& s, bool truncate) {
    s.log = std::fopen(log_path(s.name).c_str(), truncate ? "wb" : "ab");
    if (!s.log)
      std::fprintf(stderr, "symbus: cannot open stream log %s: %s\n",
                   log_path(s.name).c_str(), std::strerror(errno));
  }

  void write_frame(Stream& s, const Writer& w) {
    std::string f = w.frame();
    std::fwrite(f.data(), 1, f.size(), s.log);
    std::fflush(s.log);
  }

  void append_meta(Stream& s) {
    json::Value m = json::Value::object();
    json::Value subj = json::Value::array();
    for (const auto& p : s.subjects) subj.push_back(json::Value(p));
    m.set("subjects", std::move(subj));
    m.set("ack_wait_ms", json::Value((double)s.ack_wait_ms));
    m.set("max_deliver", json::Value((double)s.max_deliver));
    // last_seq must survive a snapshot with zero live messages, else a
    // fully-acked stream restarts numbering below the group floors and new
    // publishes get swallowed as already-acked
    m.set("last_seq", json::Value((double)s.last_seq));
    Writer w;
    w.u8(REC_META);
    w.data(m.dump());
    write_frame(s, w);
  }

  void append_msg(Stream& s, const StreamMsg& m) {
    Writer w;
    w.u8(REC_MSG);
    w.u64(m.seq);
    w.str(m.subject);
    w.u16((uint16_t)m.headers.size());
    for (const auto& [k, v] : m.headers) {
      w.str(k);
      w.str(v);
    }
    w.data(m.data);
    write_frame(s, w);
  }

  void append_ack(Stream& s, const std::string& group, uint64_t seq) {
    Writer w;
    w.u8(REC_ACK);
    w.str(group);
    w.u64(seq);
    write_frame(s, w);
  }

  void append_group(Stream& s, const ConsumerGroup& g) {
    Writer w;
    w.u8(REC_GROUP);
    w.str(g.name);
    w.u64(g.ack_floor);
    w.u32((uint32_t)g.acked.size());
    for (uint64_t seq : g.acked) w.u64(seq);
    write_frame(s, w);
  }

  // Rewrite the log as a snapshot of live state (meta + group floors + the
  // still-unacked messages), dropping the full append history. Called after
  // replay, so each restart bounds the log to what is actually outstanding.
  // Snapshot goes to a temp file first and renames over the old log, so a
  // crash mid-compaction leaves the previous log intact (never truncate the
  // only durable copy in place).
  void compact(Stream& s) {
    std::string tmp = log_path(s.name) + ".tmp";
    FILE* f = std::fopen(tmp.c_str(), "wb");
    if (!f) {
      std::fprintf(stderr, "symbus: cannot write %s: %s\n", tmp.c_str(),
                   std::strerror(errno));
      open_log(s, /*truncate=*/false);  // keep appending to the old log
      return;
    }
    FILE* prev = s.log;
    s.log = f;
    append_meta(s);
    for (auto& [gname, g] : s.groups) append_group(s, g);
    for (auto& [seq, m] : s.msgs) append_msg(s, m);
    std::fclose(f);
    s.log = prev;
    if (std::rename(tmp.c_str(), log_path(s.name).c_str()) != 0) {
      std::fprintf(stderr, "symbus: rename %s failed: %s\n", tmp.c_str(),
                   std::strerror(errno));
      std::remove(tmp.c_str());
      open_log(s, /*truncate=*/false);
      return;
    }
    open_log(s, /*truncate=*/false);  // append future records to the snapshot
  }

  void replay_all() {
    // scan data_dir for *.symlog
    std::string cmd_dir = data_dir_;
    DIR* d = ::opendir(cmd_dir.c_str());
    if (!d) return;
    struct dirent* e;
    while ((e = ::readdir(d)) != nullptr) {
      std::string fn = e->d_name;
      const std::string suffix = ".symlog";
      if (fn.size() <= suffix.size() ||
          fn.compare(fn.size() - suffix.size(), suffix.size(), suffix) != 0)
        continue;
      replay_one(fn.substr(0, fn.size() - suffix.size()));
    }
    ::closedir(d);
  }

  void replay_one(const std::string& name) {
    FILE* f = std::fopen(log_path(name).c_str(), "rb");
    if (!f) return;
    Stream& s = streams_[name];
    s.name = name;
    std::string buf;
    char chunk[65536];
    size_t n;
    while ((n = std::fread(chunk, 1, sizeof(chunk), f)) > 0) buf.append(chunk, n);
    std::fclose(f);
    size_t off = 0;
    while (off + 4 <= buf.size()) {
      uint32_t len = 0;
      for (int i = 0; i < 4; ++i)
        len |= ((uint32_t)(uint8_t)buf[off + i]) << (8 * i);
      if (len == 0 || off + 4 + len > buf.size()) break;  // torn tail: stop
      try {
        Reader r(buf.data() + off + 4, len);
        uint8_t rec = r.u8();
        if (rec == REC_META) {
          json::Value m = json::parse(r.data());
          s.subjects.clear();
          for (const auto& v : m.at("subjects").as_array())
            s.subjects.push_back(v.as_string());
          s.ack_wait_ms = (int64_t)m.at("ack_wait_ms").as_number();
          s.max_deliver = (uint32_t)m.at("max_deliver").as_number();
          if (m.has("last_seq"))
            s.last_seq = std::max(s.last_seq,
                                  (uint64_t)m.at("last_seq").as_number());
        } else if (rec == REC_MSG) {
          StreamMsg msg;
          msg.seq = r.u64();
          msg.subject = r.str();
          uint16_t nh = r.u16();
          for (uint16_t i = 0; i < nh; ++i) {
            std::string k = r.str();
            msg.headers.emplace_back(k, r.str());
          }
          msg.data = r.data();
          s.last_seq = std::max(s.last_seq, msg.seq);
          s.msgs[msg.seq] = std::move(msg);
        } else if (rec == REC_ACK) {
          std::string group = r.str();
          uint64_t seq = r.u64();
          ConsumerGroup& g = s.groups[group];
          if (g.name.empty()) g.name = group;
          g.ack(seq);
        } else if (rec == REC_GROUP) {
          std::string group = r.str();
          ConsumerGroup& g = s.groups[group];
          if (g.name.empty()) g.name = group;
          g.ack_floor = r.u64();
          uint32_t n = r.u32();
          for (uint32_t i = 0; i < n; ++i) g.acked.insert(r.u64());
        }
      } catch (const std::exception&) {
        break;  // corrupt record: stop replay at last good frame
      }
      off += 4 + len;
    }
    // consumers resume after the acked prefix
    for (auto& [gname, g] : s.groups) g.next_seq = g.ack_floor + 1;
    maybe_gc(s);
    compact(s);
  }

  std::string data_dir_;
  DeliverFn deliver_;
  std::map<std::string, Stream> streams_;
};

}  // namespace symbus
