// symbus broker — the framework-native message bus server.
//
// Replaces the reference's external NATS container (reference:
// docker-compose.yml:27-35) with ~400 lines of dependency-free C++:
// pub/sub with NATS-style wildcards, queue groups (round-robin), reply
// passthrough for inbox request-reply, and header forwarding.
//
// Concurrency model: one reader thread + one writer thread per connection;
// shared subscription table under one mutex. Outbound frames go through a
// bounded per-connection queue drained by the writer thread, so routing (and
// the durable-stream pump) never blocks on a socket; a consumer that lets
// kMaxOutqBytes of backlog pile up is disconnected (core-NATS-style
// slow-consumer policy).
//
// Usage: symbus_broker [--port 4233] [--host 0.0.0.0]

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <signal.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <condition_variable>
#include <cstdio>
#include <cstring>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "protocol.hpp"
#include "streams.hpp"

namespace symbus {

struct Conn;

struct Subscription {
  uint32_t sid;
  std::string pattern;
  std::string queue;
  // shared ownership: route()/pump snapshot targets and send after releasing
  // the broker mutex; holding the Conn alive through the send closes the
  // use-after-free window against a concurrent disconnect
  std::shared_ptr<Conn> conn;
};

struct Broker;

struct Conn {
  int fd;
  Broker* broker;
  std::mutex write_mu;
  std::condition_variable write_cv;
  std::deque<std::string> outq;
  size_t outq_bytes = 0;
  std::atomic<bool> open{true};
  std::thread writer;

  // Slow-consumer bound: a client that lets this much backlog pile up is
  // disconnected (the NATS slow-consumer policy) instead of blocking the
  // broker — routing/pump threads only ever touch the queue, never the
  // socket, so one stuck reader can't stall other connections.
  static constexpr size_t kMaxOutqBytes = 64u * 1024 * 1024;

  explicit Conn(int fd_, Broker* b) : fd(fd_), broker(b) {
    writer = std::thread([this] { writer_loop(); });
  }

  ~Conn() {
    if (writer.joinable()) {
      poison();
      writer.join();
    }
  }

  // Enqueue a frame for the writer thread; never blocks on the socket.
  bool send_all(const std::string& bytes) {
    {
      std::lock_guard<std::mutex> lk(write_mu);
      if (!open) return false;
      if (outq_bytes + bytes.size() > kMaxOutqBytes) {
        // fallthrough to poison below, outside the lock
      } else {
        outq_bytes += bytes.size();
        outq.push_back(bytes);
        write_cv.notify_one();
        return true;
      }
    }
    poison();  // slow consumer: cut it loose rather than stall the broker
    return false;
  }

  // Idempotent kill switch: wakes the writer, unblocks the reader and any
  // in-flight send. close(fd) happens once, in serve_conn, after join.
  void poison() {
    open = false;
    write_cv.notify_all();
    ::shutdown(fd, SHUT_RDWR);
  }

  void writer_loop() {
    for (;;) {
      std::string frame;
      {
        std::unique_lock<std::mutex> lk(write_mu);
        write_cv.wait(lk, [this] { return !outq.empty() || !open; });
        if (!open) break;  // poisoned: pending frames are dropped
        frame = std::move(outq.front());
        outq.pop_front();
        outq_bytes -= frame.size();
      }
      size_t off = 0;
      while (off < frame.size()) {
        ssize_t k = ::send(fd, frame.data() + off, frame.size() - off,
                           MSG_NOSIGNAL);
        if (k <= 0) {
          poison();
          return;
        }
        off += (size_t)k;
      }
    }
  }
};

struct Broker {
  std::mutex mu;
  std::vector<Subscription> subs;
  std::map<std::string, uint64_t> rr;  // (pattern|queue) -> round robin counter
  std::atomic<uint64_t> published{0}, delivered{0};

  // durable streams (lock order: stream_mu BEFORE mu — capture/pump take
  // stream_mu then call route which takes mu; never the reverse)
  std::mutex stream_mu;
  StreamEngine streams;

  void add_sub(std::shared_ptr<Conn> c, uint32_t sid,
               const std::string& pattern, const std::string& queue) {
    std::lock_guard<std::mutex> lk(mu);
    subs.push_back(Subscription{sid, pattern, queue, std::move(c)});
  }

  void remove_sub(const Conn* c, uint32_t sid) {
    std::lock_guard<std::mutex> lk(mu);
    for (size_t i = 0; i < subs.size();) {
      if (subs[i].conn.get() == c && subs[i].sid == sid)
        subs.erase(subs.begin() + (long)i);
      else
        ++i;
    }
  }

  void drop_conn(const Conn* c) {
    std::lock_guard<std::mutex> lk(mu);
    for (size_t i = 0; i < subs.size();) {
      if (subs[i].conn.get() == c)
        subs.erase(subs.begin() + (long)i);
      else
        ++i;
    }
  }

  int route(const std::string& subject, const std::string& reply,
            const std::vector<std::pair<std::string, std::string>>& headers,
            const std::string& data) {
    published++;
    // snapshot matching subs under the lock; send outside it (shared_ptr
    // keeps each Conn alive until the enqueue returns)
    struct Target {
      std::shared_ptr<Conn> conn;
      uint32_t sid;
    };
    std::vector<Target> targets;
    {
      std::lock_guard<std::mutex> lk(mu);
      // queue groups: collect members per (pattern, queue), pick round-robin
      std::map<std::string, std::vector<size_t>> groups;
      for (size_t i = 0; i < subs.size(); ++i) {
        if (!subject_matches(subs[i].pattern, subject)) continue;
        if (subs[i].queue.empty()) {
          targets.push_back({subs[i].conn, subs[i].sid});
        } else {
          groups[subs[i].pattern + "|" + subs[i].queue].push_back(i);
        }
      }
      for (auto& kv : groups) {
        uint64_t n = rr[kv.first]++;
        const Subscription& s = subs[kv.second[n % kv.second.size()]];
        targets.push_back({s.conn, s.sid});
      }
    }
    if (targets.empty()) return 0;
    for (auto& t : targets) {
      Writer w;
      w.u8(OP_MSG);
      w.u32(t.sid);
      w.str(subject);
      w.str(reply);
      w.u16((uint16_t)headers.size());
      for (auto& h : headers) {
        w.str(h.first);
        w.str(h.second);
      }
      w.data(data);
      if (t.conn->open && t.conn->send_all(w.frame())) {
        delivered++;
      } else {
        t.conn->open = false;  // reader thread will clean up
      }
    }
    return (int)targets.size();
  }

  // control-plane publishes (reserved subjects); returns true when consumed
  bool handle_control(const std::string& subject, const std::string& reply,
                      const std::string& data) {
    std::string out;
    if (subject == "_SYMBUS.stream.create") {
      std::lock_guard<std::mutex> lk(stream_mu);
      try {
        out = streams.handle_stream_create(data);
      } catch (const std::exception& e) {
        out = std::string("{\"ok\": false, \"error\": \"") + e.what() + "\"}";
      }
    } else if (subject == "_SYMBUS.consumer.create") {
      std::lock_guard<std::mutex> lk(stream_mu);
      try {
        out = streams.handle_consumer_create(data);
      } catch (const std::exception& e) {
        out = std::string("{\"ok\": false, \"error\": \"") + e.what() + "\"}";
      }
    } else if (subject == "_SYMBUS.ack") {
      std::lock_guard<std::mutex> lk(stream_mu);
      try {
        out = streams.handle_ack(data);
      } catch (const std::exception& e) {
        out = std::string("{\"ok\": false, \"error\": \"") + e.what() + "\"}";
      }
    } else if (subject == "_SYMBUS.stats") {
      std::lock_guard<std::mutex> lk(stream_mu);
      out = streams.stats_json();
    } else {
      return false;
    }
    if (!reply.empty()) route(reply, "", {}, out);
    return true;
  }
};

static bool read_exact(int fd, char* buf, size_t n) {
  size_t off = 0;
  while (off < n) {
    ssize_t k = ::recv(fd, buf + off, n - off, 0);
    if (k <= 0) return false;
    off += (size_t)k;
  }
  return true;
}

static void serve_conn(std::shared_ptr<Conn> conn) {
  Broker* broker = conn->broker;
  std::vector<char> body;
  for (;;) {
    char lenbuf[4];
    if (!read_exact(conn->fd, lenbuf, 4)) break;
    uint32_t len = 0;
    for (int i = 0; i < 4; ++i) len |= ((uint32_t)(uint8_t)lenbuf[i]) << (8 * i);
    if (len == 0 || len > MAX_FRAME) break;
    body.resize(len);
    if (!read_exact(conn->fd, body.data(), len)) break;
    try {
      Reader r(body.data(), len);
      uint8_t op = r.u8();
      switch (op) {
        case OP_SUB: {
          uint32_t sid = r.u32();
          std::string pattern = r.str();
          std::string queue = r.str();
          broker->add_sub(conn, sid, pattern, queue);
          break;
        }
        case OP_UNSUB: {
          uint32_t sid = r.u32();
          broker->remove_sub(conn.get(), sid);
          break;
        }
        case OP_PUB: {
          std::string subject = r.str();
          std::string reply = r.str();
          uint16_t nh = r.u16();
          std::vector<std::pair<std::string, std::string>> headers;
          headers.reserve(nh);
          for (uint16_t i = 0; i < nh; ++i) {
            std::string k = r.str();
            std::string v = r.str();
            headers.emplace_back(std::move(k), std::move(v));
          }
          std::string data = r.data();
          if (broker->handle_control(subject, reply, data)) break;
          // durable capture BEFORE fan-out (at-least-once: persisted even if
          // no live subscriber); reserved + inbox subjects never match stream
          // subject sets by convention, and capture() checks patterns anyway
          if (subject.rfind("_SYMBUS.", 0) != 0 && subject.rfind("_INBOX.", 0) != 0) {
            std::lock_guard<std::mutex> lk(broker->stream_mu);
            broker->streams.capture(subject, headers, data);
          }
          broker->route(subject, reply, headers, data);
          break;
        }
        case OP_PING: {
          Writer w;
          w.u8(OP_PONG);
          conn->send_all(w.frame());
          break;
        }
        default: {
          Writer w;
          w.u8(OP_ERR);
          w.str("unknown op");
          conn->send_all(w.frame());
        }
      }
    } catch (const std::exception& e) {
      Writer w;
      w.u8(OP_ERR);
      w.str(e.what());
      conn->send_all(w.frame());
      break;
    }
  }
  conn->poison();
  conn->writer.join();
  broker->drop_conn(conn.get());
  ::close(conn->fd);
}

}  // namespace symbus

int main(int argc, char** argv) {
  using namespace symbus;
  int port = 4233;
  std::string host = "0.0.0.0";
  std::string data_dir;  // empty: streams live in memory only
  for (int i = 1; i < argc - 1; ++i) {
    if (!strcmp(argv[i], "--port")) port = atoi(argv[i + 1]);
    if (!strcmp(argv[i], "--host")) host = argv[i + 1];
    if (!strcmp(argv[i], "--data-dir")) data_dir = argv[i + 1];
  }
  signal(SIGPIPE, SIG_IGN);

  int lfd = ::socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons((uint16_t)port);
  inet_pton(AF_INET, host.c_str(), &addr.sin_addr);
  if (bind(lfd, (sockaddr*)&addr, sizeof addr) != 0) {
    perror("bind");
    return 1;
  }
  if (listen(lfd, 128) != 0) {
    perror("listen");
    return 1;
  }
  fprintf(stderr, "symbus broker listening on %s:%d\n", host.c_str(), port);
  fflush(stderr);

  Broker broker;
  broker.streams.configure(
      data_dir,
      [&broker](const std::string& subject, const HeaderList& headers,
                const std::string& data) {
        return broker.route(subject, "", headers, data);
      });
  // delivery/redelivery pump for durable consumer groups
  std::thread([&broker] {
    for (;;) {
      {
        std::lock_guard<std::mutex> lk(broker.stream_mu);
        broker.streams.pump();
      }
      struct timespec ts {0, 100 * 1000000};
      nanosleep(&ts, nullptr);
    }
  }).detach();

  for (;;) {
    int cfd = ::accept(lfd, nullptr, nullptr);
    if (cfd < 0) continue;
    setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
    auto conn = std::make_shared<Conn>(cfd, &broker);
    std::thread(serve_conn, conn).detach();
  }
}
