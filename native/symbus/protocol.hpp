// symbus wire protocol — shared by the C++ broker and all clients.
//
// The reference's DCN fabric is a stock NATS server in a container
// (reference: docker-compose.yml:27-35). symbus is the framework-native
// equivalent: subjects, wildcard matching, queue groups, inbox request-reply,
// and header propagation, over a length-prefixed binary TCP protocol.
//
// frame  := u32le body_len | body
// body   := u8 op | op-specific payload     (strings are u16le len + bytes,
//                                            data is u32le len + bytes)
// ops:
//   C→S  SUB   (1): u32 sid | str subject | str queue
//   C→S  UNSUB (2): u32 sid
//   C→S  PUB   (3): str subject | str reply | u16 nh | (str k, str v)* | data
//   C→S  PING  (4)
//   S→C  MSG   (5): u32 sid | str subject | str reply | u16 nh | (str,str)* | data
//   S→C  PONG  (6)
//   S→C  ERR   (7): str message
#pragma once

#include <cstdint>
#include <cstring>
#include <map>
#include <stdexcept>
#include <string>
#include <vector>

namespace symbus {

enum Op : uint8_t {
  OP_SUB = 1,
  OP_UNSUB = 2,
  OP_PUB = 3,
  OP_PING = 4,
  OP_MSG = 5,
  OP_PONG = 6,
  OP_ERR = 7,
};

// payloads are binary-safe (length-prefixed): embeddings ride as binary
// tensor frames (services/common.hpp) with JSON as the negotiated fallback
constexpr uint32_t MAX_FRAME = 64 * 1024 * 1024;

struct Writer {
  std::string buf;
  void u8(uint8_t v) { buf.push_back((char)v); }
  void u16(uint16_t v) {
    buf.push_back((char)(v & 0xff));
    buf.push_back((char)(v >> 8));
  }
  void u32(uint32_t v) {
    for (int i = 0; i < 4; ++i) buf.push_back((char)((v >> (8 * i)) & 0xff));
  }
  void u64(uint64_t v) {
    for (int i = 0; i < 8; ++i) buf.push_back((char)((v >> (8 * i)) & 0xff));
  }
  void str(const std::string& s) {
    if (s.size() > 0xffff) throw std::runtime_error("string too long");
    u16((uint16_t)s.size());
    buf.append(s);
  }
  void data(const std::string& d) {
    u32((uint32_t)d.size());
    buf.append(d);
  }
  // final frame with length prefix
  std::string frame() const {
    std::string out;
    uint32_t n = (uint32_t)buf.size();
    for (int i = 0; i < 4; ++i) out.push_back((char)((n >> (8 * i)) & 0xff));
    out += buf;
    return out;
  }
};

struct Reader {
  const char* p;
  size_t n;
  size_t off = 0;
  Reader(const char* data, size_t len) : p(data), n(len) {}
  void need(size_t k) const {
    if (off + k > n) throw std::runtime_error("truncated frame");
  }
  uint8_t u8() {
    need(1);
    return (uint8_t)p[off++];
  }
  uint16_t u16() {
    need(2);
    uint16_t v = (uint8_t)p[off] | ((uint16_t)(uint8_t)p[off + 1] << 8);
    off += 2;
    return v;
  }
  uint32_t u32() {
    need(4);
    uint32_t v = 0;
    for (int i = 0; i < 4; ++i) v |= ((uint32_t)(uint8_t)p[off + i]) << (8 * i);
    off += 4;
    return v;
  }
  uint64_t u64() {
    need(8);
    uint64_t v = 0;
    for (int i = 0; i < 8; ++i) v |= ((uint64_t)(uint8_t)p[off + i]) << (8 * i);
    off += 8;
    return v;
  }
  std::string str() {
    uint16_t k = u16();
    need(k);
    std::string s(p + off, k);
    off += k;
    return s;
  }
  std::string data() {
    uint32_t k = u32();
    need(k);
    std::string s(p + off, k);
    off += k;
    return s;
  }
};

// NATS-style subject matching: '.' tokens, '*' one token, '>' trailing tail.
inline bool subject_matches(const std::string& pattern, const std::string& subject) {
  size_t pi = 0, si = 0;
  while (pi < pattern.size()) {
    size_t pe = pattern.find('.', pi);
    if (pe == std::string::npos) pe = pattern.size();
    std::string ptok = pattern.substr(pi, pe - pi);
    if (ptok == ">") return si <= subject.size();
    if (si > subject.size()) return false;
    size_t se = subject.find('.', si);
    if (se == std::string::npos) se = subject.size();
    std::string stok = subject.substr(si, se - si);
    if (si == subject.size() && stok.empty()) return false;
    if (ptok != "*" && ptok != stok) return false;
    pi = pe + 1;
    si = se + 1;
  }
  // pattern consumed; subject must be consumed too (si ran past end)
  return si > subject.size();
}

}  // namespace symbus
